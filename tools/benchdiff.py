"""BENCH JSON regression sentinel (docs/OBSERVABILITY.md "Device &
compiler telemetry" — the benchdiff workflow).

The bench trajectory (BENCH_r01.json, r02, ...) has so far been guarded
by eyeballs: a PR that quietly cost 20% of decode throughput would land
green.  ``benchdiff`` compares two BENCH captures **fingerprint-aware**
(the ``bench_fingerprint()`` PR 8 put in every capture):

* **same ``config_hash``** — the two runs measured the same default
  engine, so the numbers are comparable: every top-level leg metric is
  held to a hard relative threshold and any regression exits nonzero
  (the CI contract).
* **different ``config_hash``** — a PR changed engine defaults, so
  every leg moved for config reasons; the comparison is REPORT-ONLY
  (printed, exit 0) because a hard gate would either mask real
  regressions behind "the hash changed" or block every default-changing
  PR on noise.

Only **top-level numeric leg metrics** with a recognizable direction
are compared — ``*_tok_s`` / ``*_speedup`` / ``goodput_qps_*`` / ``mfu``
up-is-better, ``*_ttft*`` / ``*_ms*`` / ``*_ema`` down-is-better.
Nested diagnostic subtrees (``*_request_metrics``, ``train_metrics``,
SLO curves, chaos variant tallies) are deliberately skipped: they are
post-mortem material, not gateable headline numbers.

CLI::

    python -m tools.benchdiff OLD.json NEW.json [--threshold 0.15]
    python -m tools.benchdiff --smoke       # tier-1 self-check (asserts)
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

# direction markers matched against the (lowercased) metric name;
# first match wins, unmatched names are skipped as directionless
_HIGHER_BETTER = ("tok_s", "speedup", "goodput", "mfu", "hit_rate",
                  "acceptance_rate", "bw_util", "vs_baseline")
_LOWER_BETTER = ("ttft", "tpot", "_ms", "ms_per", "ema", "latency")


def metric_direction(name: str) -> Optional[int]:
    """+1 up-is-better, -1 down-is-better, None not gateable.  The
    headline ``value`` key (the gpt2s tokens/s number) is up-is-better
    by definition of the bench."""
    low = name.lower()
    if low == "value" or any(m in low for m in _HIGHER_BETTER):
        return 1
    if any(m in low for m in _LOWER_BETTER):
        return -1
    return None


def _leg_metrics(bench: Dict[str, Any]) -> Dict[str, float]:
    """Top-level numeric leg metrics with a direction (bools are not
    metrics; nested dicts are diagnostics and skipped)."""
    out = {}
    for k, v in bench.items():
        if isinstance(v, bool) or not isinstance(v, (int, float)):
            continue
        if metric_direction(k) is not None:
            out[k] = float(v)
    return out


def compare(old: Dict[str, Any], new: Dict[str, Any],
            threshold: float = 0.15) -> Dict[str, Any]:
    """Compare two BENCH captures; returns the verdict dict::

        {"fingerprint_match": bool, "enforced": bool,
         "regressions": [...], "improvements": [...], "unchanged": n,
         "only_old": [...], "only_new": [...], "ok": bool}

    ``ok`` is False only for an ENFORCED (matching-fingerprint) run
    with regressions; a mismatched fingerprint reports but never
    fails.  A leg metric present in ``old`` but absent from ``new``
    counts as a regression too — a silently dropped bench leg must not
    read as green (error keys like ``<leg>_error`` mark the drop)."""
    old_fp = (old.get("config_hash"), old.get("engine_version"))
    new_fp = (new.get("config_hash"), new.get("engine_version"))
    match = old_fp[0] is not None and old_fp[0] == new_fp[0]
    om, nm = _leg_metrics(old), _leg_metrics(new)
    regressions: List[Dict[str, Any]] = []
    improvements: List[Dict[str, Any]] = []
    unchanged = 0
    for k in sorted(set(om) & set(nm)):
        d = metric_direction(k)
        o, n = om[k], nm[k]
        if o == 0:
            unchanged += 1
            continue
        rel = (n - o) / abs(o)
        entry = {"metric": k, "old": o, "new": n,
                 "rel_change": round(rel, 4)}
        if d * rel < -threshold:
            regressions.append(entry)
        elif d * rel > threshold:
            improvements.append(entry)
        else:
            unchanged += 1
    only_old = sorted(set(om) - set(nm))
    only_new = sorted(set(nm) - set(om))
    for k in only_old:
        regressions.append({"metric": k, "old": om[k], "new": None,
                            "rel_change": None,
                            "note": "leg metric disappeared"})
    # anomaly-count deltas (``<leg>_anomalies`` subtrees, PR 10):
    # REPORTED, never gated — detector fires are workload/rig-noise
    # sensitive, but a leg that suddenly fires 40 latency anomalies is
    # exactly what a reviewer should look at next to a green diff
    anomaly_deltas: List[Dict[str, Any]] = []
    # fleet anomaly subtrees ({"fleet": {...}, "replicas": {name:
    # {...}}}, PR 14) report fleet-total AND per-replica deltas —
    # REPORTED like the flat anomaly deltas, never gated (detector
    # fires are workload/rig-noise sensitive; a replica suddenly
    # firing 40 latency anomalies is reviewer material, not a gate)
    fleet_anomaly_deltas: List[Dict[str, Any]] = []
    for k in sorted(set(old) | set(new)):
        if not k.endswith("_anomalies"):
            continue
        ov, nv = old.get(k), new.get(k)
        if any(isinstance(v, dict) and "fleet" in v for v in (ov, nv)):
            of = (ov or {}).get("fleet") if isinstance(ov, dict) else None
            nf = (nv or {}).get("fleet") if isinstance(nv, dict) else None
            o = of.get("total") if isinstance(of, dict) else None
            n = nf.get("total") if isinstance(nf, dict) else None
            if (o or 0) != (n or 0):
                fleet_anomaly_deltas.append(
                    {"metric": f"{k}.fleet", "old": o, "new": n})
            oreps = (ov or {}).get("replicas") \
                if isinstance(ov, dict) else None
            nreps = (nv or {}).get("replicas") \
                if isinstance(nv, dict) else None
            oreps = oreps if isinstance(oreps, dict) else {}
            nreps = nreps if isinstance(nreps, dict) else {}
            for rep in sorted(set(oreps) | set(nreps)):
                ro = (oreps.get(rep) or {}).get("total")
                rn = (nreps.get(rep) or {}).get("total")
                if (ro or 0) != (rn or 0):
                    fleet_anomaly_deltas.append(
                        {"metric": f"{k}.replicas.{rep}",
                         "old": ro, "new": rn})
            continue
        o = ov.get("total") if isinstance(ov, dict) else None
        n = nv.get("total") if isinstance(nv, dict) else None
        if o is None and n is None:
            continue
        if (o or 0) != (n or 0):
            anomaly_deltas.append({"metric": k, "old": o, "new": n})
    # SLO scorecard deltas (``<leg>_slo`` subtrees, the scorecard
    # bench legs embed): per-class composite attainment and remaining
    # error budget — REPORTED, never gated, exactly like the anomaly
    # deltas (attainment moves with rig noise; a class suddenly
    # burning its budget is reviewer material next to a green diff)
    slo_deltas: List[Dict[str, Any]] = []
    for k in sorted(set(old) | set(new)):
        if not k.endswith("_slo"):
            continue
        ov, nv = old.get(k), new.get(k)
        ocl = ov.get("classes") if isinstance(ov, dict) else None
        ncl = nv.get("classes") if isinstance(nv, dict) else None
        ocl = ocl if isinstance(ocl, dict) else {}
        ncl = ncl if isinstance(ncl, dict) else {}
        for cls in sorted(set(ocl) | set(ncl)):
            for path, leaf in ((("objectives", "requests", "attainment"),
                                "attainment"),
                               (("error_budget", "remaining"),
                                "budget_remaining")):
                def _dig(tree):
                    node = tree.get(cls)
                    for part in path:
                        if not isinstance(node, dict):
                            return None
                        node = node.get(part)
                    return node
                o, n = _dig(ocl), _dig(ncl)
                if o != n:
                    slo_deltas.append(
                        {"metric": f"{k}.{cls}.{leaf}",
                         "old": o, "new": n})
    return {
        "fingerprint_match": match,
        "old_fingerprint": {"config_hash": old_fp[0],
                            "engine_version": old_fp[1]},
        "new_fingerprint": {"config_hash": new_fp[0],
                            "engine_version": new_fp[1]},
        "enforced": match,
        "threshold": threshold,
        "regressions": regressions,
        "improvements": improvements,
        "unchanged": unchanged,
        "only_old": only_old,
        "only_new": only_new,
        "anomaly_deltas": anomaly_deltas,
        "fleet_anomaly_deltas": fleet_anomaly_deltas,
        "slo_deltas": slo_deltas,
        "ok": match is False or not regressions,
    }


def diff_files(old_path: str, new_path: str,
               threshold: float = 0.15) -> Dict[str, Any]:
    with open(old_path) as f:
        old = json.load(f)
    with open(new_path) as f:
        new = json.load(f)
    return compare(old, new, threshold)


def _render(v: Dict[str, Any]) -> str:
    lines = []
    mode = "ENFORCED (same config_hash)" if v["enforced"] else \
        "REPORT-ONLY (config_hash changed — defaults moved, legs " \
        "are not comparable as regressions)"
    lines.append(f"benchdiff: {mode}, threshold ±{v['threshold']:.0%}")
    for e in v["regressions"]:
        if e.get("new") is None:
            lines.append(f"  REGRESSION {e['metric']}: "
                         f"{e['old']} -> MISSING")
        else:
            lines.append(f"  REGRESSION {e['metric']}: {e['old']} -> "
                         f"{e['new']} ({e['rel_change']:+.1%})")
    for e in v["improvements"]:
        lines.append(f"  improved   {e['metric']}: {e['old']} -> "
                     f"{e['new']} ({e['rel_change']:+.1%})")
    for e in v.get("anomaly_deltas", []):
        lines.append(f"  anomalies  {e['metric']}: {e['old']} -> "
                     f"{e['new']} (report-only, never gates)")
    for e in v.get("fleet_anomaly_deltas", []):
        lines.append(f"  fleet-anom {e['metric']}: {e['old']} -> "
                     f"{e['new']} (report-only, never gates)")
    for e in v.get("slo_deltas", []):
        lines.append(f"  slo        {e['metric']}: {e['old']} -> "
                     f"{e['new']} (report-only, never gates)")
    lines.append(f"  unchanged: {v['unchanged']}, "
                 f"new-only legs: {len(v['only_new'])}")
    lines.append("benchdiff: " + ("OK" if v["ok"] else "REGRESSED"))
    return "\n".join(lines)


# --------------------------------------------------------------------------
# smoke: the tier-1 self-check (synthetic captures, asserts)
# --------------------------------------------------------------------------

def smoke() -> Dict[str, Any]:
    """Deterministic self-check on synthetic BENCH captures: one
    regressed leg under a MATCHING fingerprint must fail; the same
    regression under a MISMATCHED fingerprint must report-only; an
    improvement must never flag; a disappeared leg must fail."""
    base = {"engine_version": "1.0", "config_hash": "aaaa",
            "value": 1000.0,                       # headline tok/s
            "pipe2_decode_tok_s": 500.0,
            "serving_ttft_p50_ms": 100.0,
            "spec_decode_speedup": 1.8,
            "goodput_qps_sla2": 2.0,
            "platform": "cpu", "steps": 40,        # directionless: skipped
            "serving_request_metrics": {"ttft_ms": {"p50": 1.0}}}

    regressed = dict(base, pipe2_decode_tok_s=350.0)       # -30% tok/s
    v = compare(base, regressed)
    assert v["enforced"] and not v["ok"], v
    assert [e["metric"] for e in v["regressions"]] \
        == ["pipe2_decode_tok_s"], v["regressions"]

    lat_regressed = dict(base, serving_ttft_p50_ms=140.0)  # +40% latency
    v = compare(base, lat_regressed)
    assert not v["ok"] and v["regressions"][0]["metric"] \
        == "serving_ttft_p50_ms", v

    mismatched = dict(regressed, config_hash="bbbb")
    v_mm = compare(base, mismatched)
    assert not v_mm["enforced"] and v_mm["ok"], v_mm       # report-only
    assert v_mm["regressions"], "mismatch must still REPORT the delta"

    improved = dict(base, pipe2_decode_tok_s=800.0,
                    serving_ttft_p50_ms=50.0)
    v_up = compare(base, improved)
    assert v_up["ok"] and len(v_up["improvements"]) == 2, v_up

    dropped = {k: v2 for k, v2 in base.items()
               if k != "spec_decode_speedup"}
    v_drop = compare(base, dropped)
    assert not v_drop["ok"] and any(
        e.get("note") == "leg metric disappeared"
        for e in v_drop["regressions"]), v_drop

    within = dict(base, pipe2_decode_tok_s=460.0)          # -8% < 15%
    assert compare(base, within)["ok"]

    # anomaly-count deltas REPORT and never gate (PR 10): a 40x fire
    # jump under a matching fingerprint stays ok=True but is listed
    noisy_base = dict(base, pipe2_anomalies={"total": 1,
                                             "by_signal": {"x": 1}})
    noisy_new = dict(base, pipe2_anomalies={"total": 40,
                                            "by_signal": {"x": 40}})
    v_an = compare(noisy_base, noisy_new)
    assert v_an["ok"], v_an
    assert v_an["anomaly_deltas"] == [
        {"metric": "pipe2_anomalies", "old": 1, "new": 40}], v_an
    assert compare(noisy_base, noisy_base)["anomaly_deltas"] == []

    # fleet anomaly subtrees (PR 14): fleet-total and per-replica
    # deltas REPORT under fleet_anomaly_deltas and CANNOT fail a run
    # even under a matching fingerprint
    fl_base = dict(base, fleet_serving_anomalies={
        "fleet": {"total": 0, "by_signal": {}},
        "replicas": {"r0": {"total": 0}, "r1": {"total": 1}}})
    fl_new = dict(base, fleet_serving_anomalies={
        "fleet": {"total": 7, "by_signal": {"storm": 7}},
        "replicas": {"r0": {"total": 40}, "r1": {"total": 1}}})
    v_fl = compare(fl_base, fl_new)
    assert v_fl["ok"], v_fl                    # reports, never gates
    assert v_fl["fleet_anomaly_deltas"] == [
        {"metric": "fleet_serving_anomalies.fleet", "old": 0, "new": 7},
        {"metric": "fleet_serving_anomalies.replicas.r0",
         "old": 0, "new": 40}], v_fl
    assert v_fl["anomaly_deltas"] == [], v_fl  # not double-reported
    assert compare(fl_base, fl_base)["fleet_anomaly_deltas"] == []

    # SLO scorecard deltas (``<leg>_slo``): per-class composite
    # attainment and budget drops REPORT under slo_deltas and CANNOT
    # fail a run even under a matching fingerprint
    def _card(att, remaining):
        return {"enabled": True, "classes": {"interactive": {
            "objectives": {"requests": {"attainment": att,
                                        "target": 0.95}},
            "error_budget": {"remaining": remaining}}}}
    slo_base = dict(base, serving_slo=_card(1.0, 25))
    slo_new = dict(base, serving_slo=_card(0.5, 0))
    v_slo = compare(slo_base, slo_new)
    assert v_slo["ok"], v_slo                  # reports, never gates
    assert v_slo["slo_deltas"] == [
        {"metric": "serving_slo.interactive.attainment",
         "old": 1.0, "new": 0.5},
        {"metric": "serving_slo.interactive.budget_remaining",
         "old": 25, "new": 0}], v_slo
    assert compare(slo_base, slo_base)["slo_deltas"] == []

    return {"ok": True,
            "checks": ["enforced_regression_fails",
                       "latency_regression_fails",
                       "fingerprint_mismatch_report_only",
                       "improvement_passes",
                       "dropped_leg_fails",
                       "within_threshold_passes",
                       "anomaly_delta_reports_not_gates",
                       "fleet_anomaly_delta_reports_not_gates",
                       "slo_delta_reports_not_gates"]}


def main(argv: Optional[List[str]] = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("old", nargs="?", help="baseline BENCH JSON")
    ap.add_argument("new", nargs="?", help="candidate BENCH JSON")
    ap.add_argument("--threshold", type=float, default=0.15,
                    help="relative regression threshold per leg "
                    "(default 0.15)")
    ap.add_argument("--json", action="store_true",
                    help="emit the verdict dict as JSON")
    ap.add_argument("--smoke", action="store_true",
                    help="run the deterministic self-check (asserts; "
                    "the tier-1 leg)")
    args = ap.parse_args(argv)

    if args.smoke:
        out = smoke()
        print(json.dumps(out))  # tpulint: disable=print — CLI output
        return 0
    if not args.old or not args.new:
        ap.error("OLD and NEW BENCH JSONs required (or --smoke)")
    verdict = diff_files(args.old, args.new, args.threshold)
    if args.json:
        print(json.dumps(verdict))  # tpulint: disable=print — CLI output
    else:
        print(_render(verdict))  # tpulint: disable=print — CLI output
    return 0 if verdict["ok"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
