"""Merge a host SpanTracer Chrome trace with a ``jax.profiler`` device
artifact into ONE Perfetto-loadable timeline
(docs/OBSERVABILITY.md "Anomaly detection & deep capture").

The host trace (telemetry/tracer.py) timestamps spans on
``time.perf_counter_ns``; the jax profiler's ``*.trace.json.gz``
timestamps its events relative to the profiling session start, and its
``*.xplane.pb`` lines carry ns timestamps of their own.  Until now the
two could only be eyeballed side by side — the depth-2 dispatch-ahead
overlap (and later the T3 tile-level comm overlap, arxiv 2401.16677)
was visually verifiable only on the host half.  The capture window
(telemetry/profiler.py) records a clock anchor — ``perf_counter_ns``
and ``epoch ns`` at the instant the session started — and this tool
uses it to shift device events onto the host ``perf_counter``
timeline, so host stages (schedule / stage / dispatch / wait /
readback, each span carrying its step ``sid``) and device/XLA activity
(including the ``jax.named_scope`` labels from ``comm/collectives.py``)
render as tracks of ONE Perfetto file.

Device-artifact handling, in preference order:

* ``*.trace.json.gz`` under the capture's ``device/`` dir — already
  Chrome-trace events, session-relative microseconds; shifted by the
  anchor and merged as-is.
* ``*.xplane.pb`` — decoded by the minimal pure-python protobuf reader
  below (XSpace/XPlane/XLine/XEvent; no tensorflow/xprof dependency),
  for jaxlib builds that emit only the xplane.
* neither — the merge still completes, host-only, and says so loudly
  in ``otherData.device_absent`` (the loud-but-absent contract).

CLI::

    python -m tools.tracemerge CAPTURE_DIR [-o merged.json]
"""

from __future__ import annotations

import glob
import gzip
import json
import os
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

# --------------------------------------------------------------------------
# minimal protobuf wire-format reader (just enough for XSpace)
# --------------------------------------------------------------------------


def _read_varint(buf: bytes, i: int) -> Tuple[int, int]:
    out = 0
    shift = 0
    while True:
        b = buf[i]
        i += 1
        out |= (b & 0x7F) << shift
        if not b & 0x80:
            return out, i
        shift += 7


def _fields(buf: bytes) -> Iterator[Tuple[int, int, Any]]:
    """Yield (field_number, wire_type, value) over one message body.
    Length-delimited values come back as bytes; varints as ints;
    fixed32/64 as raw ints."""
    i = 0
    n = len(buf)
    while i < n:
        key, i = _read_varint(buf, i)
        fno, wt = key >> 3, key & 7
        if wt == 0:
            v, i = _read_varint(buf, i)
        elif wt == 2:
            ln, i = _read_varint(buf, i)
            v = buf[i:i + ln]
            i += ln
        elif wt == 1:
            v = int.from_bytes(buf[i:i + 8], "little")
            i += 8
        elif wt == 5:
            v = int.from_bytes(buf[i:i + 4], "little")
            i += 4
        else:
            raise ValueError(f"unsupported wire type {wt}")
        yield fno, wt, v


def _decode_event_metadata(buf: bytes) -> Tuple[int, str]:
    mid, name = 0, ""
    for fno, _, v in _fields(buf):
        if fno == 1:
            mid = v
        elif fno == 2:
            name = v.decode("utf-8", "replace")
        elif fno == 4 and not name:
            name = v.decode("utf-8", "replace")
    return mid, name


def _decode_xevent(buf: bytes) -> Dict[str, int]:
    ev = {"metadata_id": 0, "offset_ps": 0, "duration_ps": 0}
    for fno, _, v in _fields(buf):
        if fno == 1:
            ev["metadata_id"] = v
        elif fno == 2:
            ev["offset_ps"] = v
        elif fno == 3:
            ev["duration_ps"] = v
    return ev


def _decode_xline(buf: bytes) -> Dict[str, Any]:
    line = {"id": 0, "name": "", "timestamp_ns": 0, "events": []}
    for fno, _, v in _fields(buf):
        if fno == 1:
            line["id"] = v
        elif fno == 2:
            line["name"] = v.decode("utf-8", "replace")
        elif fno == 11 and not line["name"]:
            line["name"] = v.decode("utf-8", "replace")
        elif fno == 3:
            line["timestamp_ns"] = v
        elif fno == 4:
            line["events"].append(_decode_xevent(v))
    return line


def _decode_xplane(buf: bytes) -> Dict[str, Any]:
    plane = {"id": 0, "name": "", "lines": [], "event_metadata": {}}
    for fno, _, v in _fields(buf):
        if fno == 1:
            plane["id"] = v
        elif fno == 2:
            plane["name"] = v.decode("utf-8", "replace")
        elif fno == 3:
            plane["lines"].append(_decode_xline(v))
        elif fno == 4:
            # map<int64, XEventMetadata> entry: key=1, value=2
            k, meta = None, None
            for efno, _, ev in _fields(v):
                if efno == 1:
                    k = ev
                elif efno == 2:
                    meta = _decode_event_metadata(ev)
            if meta is not None:
                plane["event_metadata"][k if k is not None
                                        else meta[0]] = meta[1]
    return plane


def decode_xspace(buf: bytes) -> List[Dict[str, Any]]:
    """Planes of one serialized ``XSpace`` (tensorflow xplane.proto) —
    enough structure for timeline rendering: plane/line names, line
    timestamps, events with metadata-resolved names."""
    return [_decode_xplane(v) for fno, _, v in _fields(buf) if fno == 1]


def xplane_chrome_events(path: str, t_session_epoch_ns: int,
                         pid_base: int = 2000) -> List[Dict[str, Any]]:
    """Chrome trace events (session-relative microsecond ``ts``) from
    one ``*.xplane.pb``.  Line timestamps that look epoch-absolute
    (> ~3 years in ns) are rebased on the capture's epoch anchor;
    small ones are taken as session-relative already."""
    with open(path, "rb") as f:
        planes = decode_xspace(f.read())
    out: List[Dict[str, Any]] = []
    pid = pid_base
    for plane in planes:
        pid += 1
        out.append({"ph": "M", "pid": pid, "tid": 0,
                    "name": "process_name",
                    "args": {"name": plane["name"] or f"plane{pid}"}})
        for line in plane["lines"]:
            tid = int(line["id"]) & 0x7FFFFFFF
            out.append({"ph": "M", "pid": pid, "tid": tid,
                        "name": "thread_name",
                        "args": {"name": line["name"] or f"line{tid}"}})
            base_ns = line["timestamp_ns"]
            if base_ns > 10**17:          # epoch-absolute ns
                base_ns -= t_session_epoch_ns
            for ev in line["events"]:
                name = plane["event_metadata"].get(
                    ev["metadata_id"], f"event{ev['metadata_id']}")
                out.append({
                    "ph": "X", "pid": pid, "tid": tid,
                    "name": name,
                    "ts": (base_ns + ev["offset_ps"] / 1e3) / 1e3,
                    "dur": ev["duration_ps"] / 1e6,
                })
    return out


# --------------------------------------------------------------------------
# HLO op-name harvesting (named_scope labels)
# --------------------------------------------------------------------------

def _try_str(v: bytes) -> Optional[str]:
    try:
        s = v.decode("utf-8")
    except Exception:  # tpulint: disable=silent-except — utf-8 probe: most length-delimited fields are submessages, not strings
        return None
    return s if s and s.isprintable() else None


def hlo_op_name_map(xplane_path: str) -> Dict[str, Tuple[str, ...]]:
    """instruction name -> every ``metadata.op_name`` seen for it (the
    ``jax.named_scope`` paths, e.g. ``jit(f)/.../t3_mm_ar_comm_t0_ar/
    psum``), harvested from the HLO protos the profiler embeds in the
    xplane's metadata plane.

    The device timeline names events by bare HLO instruction
    (``all-reduce.4``) — the scope labels live only in each
    instruction's OpMetadata.  We walk the nested protobuf generically:
    any submessage whose field 1 is a printable string and whose
    field 7 (OpMetadata) carries a '/'-scoped field-2 string is an
    instruction/name pair.  Bare instruction names COLLIDE across
    modules (every program compiled in the process embeds metadata, and
    ``all-reduce.4`` of one module is unrelated to another's), and the
    timeline events carry no module identity to disambiguate by — so
    ALL distinct op_names per instruction are kept, in walk order, and
    the annotation surfaces every candidate rather than letting
    whichever module was walked first shadow the rest."""
    with open(xplane_path, "rb") as f:
        buf = f.read()
    out: Dict[str, Tuple[str, ...]] = {}

    def walk(b: bytes, depth: int) -> None:
        if depth > 12:
            return
        try:
            fs = list(_fields(b))
        except Exception:  # tpulint: disable=silent-except — wire probe: string payloads misparse as submessages by design
            return
        name = op = None
        for fno, wt, v in fs:
            if wt != 2:
                continue
            s = _try_str(v)
            if s is not None:
                if fno == 1 and name is None:
                    name = s
                continue
            if fno == 7:
                try:
                    for f2, w2, v2 in _fields(v):
                        if f2 == 2 and w2 == 2:
                            s2 = _try_str(v2)
                            if s2 and "/" in s2:
                                op = s2
                except Exception:  # tpulint: disable=silent-except — wire probe: field 7 need not be OpMetadata everywhere
                    pass
            walk(v, depth + 1)
        if name and op:
            have = out.get(name, ())
            if op not in have:
                out[name] = have + (op,)

    # walk ONLY each plane's event_metadata table (field 4) — the HLO
    # protos live there; the event lines (field 3) are the bulk of a
    # real capture's bytes and contain no names worth harvesting
    try:
        for fno, _, plane in _fields(buf):
            if fno != 1:
                continue
            for f2, w2, v2 in _fields(plane):
                if f2 == 4 and w2 == 2:
                    walk(v2, 0)
    except Exception as e:
        # a corrupt/truncated xplane (or a layout change in a new
        # jaxlib) must say so — a silent empty map would later surface
        # as a misleading "no device event carries scope" violation
        print(f"tracemerge: xplane op-name harvest failed on "  # tpulint: disable=print — CLI/loud-degradation output
              f"{xplane_path}: {type(e).__name__}: {e}; merged "
              "timeline will lack scoped op_name annotations")
    return out


def annotate_op_names(events: List[Dict[str, Any]],
                      op_names: Dict[str, Tuple[str, ...]]) -> int:
    """Attach ``args.op_name`` to duration events whose bare
    instruction name is in the map; returns how many were annotated.
    Cross-module name collisions surface EVERY candidate (joined with
    `` | ``) — the window genuinely executed an instruction of that
    name, and hiding all but one module's scope made the timeline (and
    ``validate_merged_trace``'s scope check) depend on protobuf walk
    order."""
    n = 0
    for ev in events:
        if not isinstance(ev, dict) or ev.get("ph") != "X":
            continue
        scoped = op_names.get(ev.get("name", ""))
        if scoped:
            args = ev.setdefault("args", {})
            if isinstance(args, dict):
                args["op_name"] = " | ".join(scoped)
                n += 1
    return n


# --------------------------------------------------------------------------
# device-artifact loading
# --------------------------------------------------------------------------

def load_device_events(device_dir: str,
                       t_session_epoch_ns: int) -> List[Dict[str, Any]]:
    """Chrome events (session-relative µs) from a jax profiler log dir:
    prefers the ``trace.json.gz`` the profiler already renders, falls
    back to decoding ``xplane.pb`` directly.  Either way, events whose
    instruction appears in the xplane's HLO metadata gain an
    ``args.op_name`` with the full ``jax.named_scope`` path — the T3
    tile-comm scopes are only visible through it on backends (XLA:CPU)
    whose timeline names events by bare instruction."""
    pbs = sorted(glob.glob(os.path.join(device_dir, "**", "*.xplane.pb"),
                           recursive=True))
    gz = sorted(glob.glob(os.path.join(device_dir, "**",
                                       "*.trace.json.gz"),
                          recursive=True))
    events: List[Dict[str, Any]] = []
    if gz:
        with gzip.open(gz[-1], "rt") as f:
            events = json.load(f).get("traceEvents", [])
    elif pbs:
        events = xplane_chrome_events(pbs[-1], t_session_epoch_ns)
    if events and pbs:
        # TPU-style traces already name events by scoped op path; only
        # harvest the xplane when the timeline carries bare instruction
        # names (XLA:CPU) — the protobuf walk is not free
        def scoped(e):
            n = e.get("name", "")
            # "$"-prefixed names are the host Python tracer's
            # file-path frames, not XLA op paths
            return "/" in n and not n.startswith("$")

        if not any(isinstance(e, dict) and e.get("ph") == "X"
                   and scoped(e) for e in events):
            annotate_op_names(events, hlo_op_name_map(pbs[-1]))
    return events


# --------------------------------------------------------------------------
# merge
# --------------------------------------------------------------------------

def merge_events(host_events: List[Dict[str, Any]],
                 device_events: List[Dict[str, Any]],
                 t_start_perf_ns: int) -> List[Dict[str, Any]]:
    """Put both event streams on the host ``perf_counter`` timeline
    (microseconds): host events already are; device events are
    session-relative and get shifted by the capture's anchor.  Device
    pids are bumped out of the host's pid space so Perfetto renders
    host stages and device activity as separate process groups."""
    anchor_us = t_start_perf_ns / 1e3
    out: List[Dict[str, Any]] = list(host_events)
    for ev in device_events:
        if not isinstance(ev, dict) or "ph" not in ev:
            continue      # the profiler emits a trailing partial record
        ev = dict(ev)
        pid = ev.get("pid", 0)
        ev["pid"] = pid + 10_000 if pid < 10_000 else pid
        if ev.get("ph") in ("X", "i", "b", "e") and "ts" in ev:
            ev["ts"] = ev["ts"] + anchor_us
        out.append(ev)
    return out


def _capture_events(capture_dir: str
                    ) -> Tuple[List[Dict[str, Any]], Dict[str, Any],
                               int, bool]:
    """One capture window's events, already merged onto the host
    ``perf_counter`` timeline (µs): host spans as recorded, device
    events shifted by the capture's OWN clock anchor.  Returns
    ``(events, meta, n_host_events, device_absent)`` — the shared core
    of :func:`merge_capture` and :func:`merge_fleet` (each capture is
    clock-anchored per artifact, so a fleet merge aligns N windows
    from N replicas on one timeline)."""
    with open(os.path.join(capture_dir, "meta.json")) as f:
        meta = json.load(f)
    host: Dict[str, Any] = {"traceEvents": []}
    if meta.get("host_trace"):
        with open(os.path.join(capture_dir, meta["host_trace"])) as f:
            host = json.load(f)
    device_events: List[Dict[str, Any]] = []
    device_absent = True
    if meta.get("device_dir"):
        ddir = os.path.join(capture_dir, meta["device_dir"])
        if os.path.isdir(ddir):
            device_events = load_device_events(
                ddir, meta.get("t_start_epoch_ns", 0))
            device_absent = not device_events
    host_events = host.get("traceEvents", [])
    return (merge_events(host_events, device_events,
                         meta["t_start_perf_ns"]),
            meta, len(host_events), device_absent)


def merge_capture(capture_dir: str,
                  out_path: Optional[str] = None) -> str:
    """Merge one capture window's artifacts
    (telemetry/profiler.py layout: ``meta.json`` + ``host_trace.json``
    + ``device/``) into a single Perfetto-loadable Chrome trace;
    returns the written path (default ``<capture_dir>/merged.json``)."""
    events, meta, n_host, device_absent = _capture_events(capture_dir)
    if device_absent:
        print(f"tracemerge: NO device events under {capture_dir} — "  # tpulint: disable=print — CLI/loud-degradation output
              "emitting a host-only timeline (profiler absent or "
              "unsupported on this backend/build)")
    merged = {
        "displayTimeUnit": "ms",
        "traceEvents": events,
        "otherData": {
            "merged_by": "tools/tracemerge",
            "capture": meta,
            "host_events": n_host,
            "device_events": len(events) - n_host,
            "device_absent": device_absent,
        },
    }
    out_path = out_path or os.path.join(capture_dir, "merged.json")
    with open(out_path, "w") as f:
        json.dump(merged, f)
    return out_path


# --------------------------------------------------------------------------
# fleet merge: router trace + N replica capture artifacts
# --------------------------------------------------------------------------

# per-replica pid stride in a --fleet merge: replica i's events (host
# AND device — the capture's own +10000 device bump rides inside) are
# shifted by (i+1) * stride, so each replica renders as its own
# Perfetto process group while the router trace keeps the base pids
_FLEET_PID_STRIDE = 100_000


def merge_fleet(fleet_dir: str, out_path: Optional[str] = None) -> str:
    """Merge a fleet post-mortem bundle (``FleetRouter.debug_dump``
    layout: ``fleet.json`` + ``router_trace.json`` + per-replica
    capture artifacts) onto ONE Perfetto timeline
    (docs/OBSERVABILITY.md "Fleet observability").

    The router's span ring — placement / migrate / failover spans and
    journey instants, each carrying ``uid`` + ``replica`` args — stays
    at the base pids; every replica's capture windows merge through
    their OWN clock anchors (all replicas share the in-process
    ``perf_counter`` clock) and are shifted into a per-replica pid
    range, so one request's journey is flow-connectable across the
    router track and the replica process groups by its shared ``uid``
    arg.  Replicas whose captures are missing are reported loudly and
    skipped — the merge still completes."""
    with open(os.path.join(fleet_dir, "fleet.json")) as f:
        dump = json.load(f)
    events: List[Dict[str, Any]] = []
    if dump.get("router_trace"):
        with open(os.path.join(fleet_dir, dump["router_trace"])) as f:
            events.extend(json.load(f).get("traceEvents", []))
    else:
        print(f"tracemerge: fleet bundle {fleet_dir} carries no "  # tpulint: disable=print — CLI/loud-degradation output
              "router trace (telemetry plane off?) — replica tracks "
              "only")
    per_replica: Dict[str, int] = {}
    device_absent = True
    for i, name in enumerate(sorted(dump.get("replicas", {}))):
        info = dump["replicas"][name]
        offset = (i + 1) * _FLEET_PID_STRIDE
        n_ev = 0
        for cdir in info.get("captures", ()):
            if not os.path.isdir(cdir):
                rel = os.path.join(fleet_dir, cdir)
                if os.path.isdir(rel):
                    cdir = rel
                else:
                    print(f"tracemerge: replica {name} capture "  # tpulint: disable=print — CLI/loud-degradation output
                          f"{cdir} missing — skipped")
                    continue
            try:
                evs, _, n_host, absent = _capture_events(cdir)
            except (OSError, ValueError, KeyError) as e:
                print(f"tracemerge: replica {name} capture {cdir} "  # tpulint: disable=print — CLI/loud-degradation output
                      f"unreadable ({type(e).__name__}: {e}) — skipped")
                continue
            device_absent = device_absent and absent
            for ev in evs:
                if not isinstance(ev, dict):
                    continue
                ev = dict(ev)
                ev["pid"] = ev.get("pid", 0) + offset
                if ev.get("name") == "process_name" \
                        and isinstance(ev.get("args"), dict):
                    ev["args"] = {**ev["args"],
                                  "name": f"replica {name}: "
                                          f"{ev['args'].get('name', '')}"}
                events.append(ev)
                n_ev += 1
        per_replica[name] = n_ev
    merged = {
        "displayTimeUnit": "ms",
        "traceEvents": events,
        "otherData": {
            "merged_by": "tools/tracemerge --fleet",
            "fleet": {"reason": dump.get("reason"),
                      "steps": dump.get("steps")},
            "replica_events": per_replica,
            "replica_groups": sum(1 for n in per_replica.values() if n),
            "device_absent": device_absent,
        },
    }
    out_path = out_path or os.path.join(fleet_dir, "merged_fleet.json")
    with open(out_path, "w") as f:
        json.dump(merged, f)
    return out_path


def validate_merged_trace(obj: Dict[str, Any],
                          require_device: bool = True,
                          require_scopes: Sequence[str] = (),
                          require_replicas: int = 0) -> List[str]:
    """Schema check for a merged timeline: returns violations (empty
    when valid).  Valid means Chrome-trace-shaped (``traceEvents`` list
    of dicts with ``ph``), containing at least one host SpanTracer
    track (pid 1 thread_name metadata) and — unless ``require_device``
    is off — at least one device-derived duration event (pid whose
    in-group offset is >= 10000; in a ``--fleet`` merge each replica's
    events live in their own pid group of stride 100000, the device
    bump riding inside).  ``require_scopes``: substrings that must
    each match some device event's name or scoped ``args.op_name`` —
    how a test pins the T3 tile-comm scopes to actual device activity.
    ``require_replicas``: minimum number of distinct replica process
    groups a ``--fleet`` merge must carry (the multi-replica presence
    bar — a fleet timeline with one replica track explains nothing
    about the fleet)."""
    problems: List[str] = []
    evs = obj.get("traceEvents")
    if not isinstance(evs, list) or not evs:
        return ["traceEvents missing or empty"]
    if not all(isinstance(e, dict) and "ph" in e for e in evs):
        problems.append("malformed trace events (dict with 'ph' "
                        "required)")
        return problems
    host_tracks = {e["args"]["name"] for e in evs
                   if e.get("ph") == "M" and e.get("pid") == 1
                   and e.get("name") == "thread_name"
                   and isinstance(e.get("args"), dict)
                   and "name" in e["args"]}
    if not host_tracks:
        problems.append("no host SpanTracer tracks (pid 1 thread_name)")
    host_spans = [e for e in evs if e.get("pid") == 1
                  and e.get("ph") == "X"]
    if not host_spans:
        problems.append("no host span events")
    dev = [e for e in evs
           if e.get("pid", 0) % _FLEET_PID_STRIDE >= 10_000
           and e.get("ph") == "X"]
    if require_device and not dev:
        problems.append("no device-derived events (pid >= 10000)")
    if require_replicas:
        groups = {e.get("pid", 0) // _FLEET_PID_STRIDE for e in evs
                  if e.get("pid", 0) >= _FLEET_PID_STRIDE}
        if len(groups) < require_replicas:
            problems.append(
                f"{len(groups)} replica process group(s) < required "
                f"{require_replicas} (pid stride {_FLEET_PID_STRIDE})")
    for scope in require_scopes:
        if not any(scope in e.get("name", "")
                   or (isinstance(e.get("args"), dict)
                       and scope in e["args"].get("op_name", ""))
                   for e in dev):
            problems.append(
                f"no device event carries scope {scope!r} (name or "
                "args.op_name)")
    return problems


def main(argv: Optional[List[str]] = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("capture_dir",
                    help="capture window directory "
                    "(telemetry/profiler.py layout), or with --fleet "
                    "a fleet post-mortem bundle "
                    "(FleetRouter.debug_dump layout)")
    ap.add_argument("-o", "--out", default=None,
                    help="output path (default: "
                    "<capture_dir>/merged.json, or "
                    "<bundle>/merged_fleet.json with --fleet)")
    ap.add_argument("--fleet", action="store_true",
                    help="merge a fleet bundle: router trace + every "
                    "replica's capture artifacts as per-replica "
                    "process groups")
    ap.add_argument("--validate", action="store_true",
                    help="schema-check the merged file and exit "
                    "nonzero on violations (with --fleet, also "
                    "requires >= 2 replica process groups)")
    args = ap.parse_args(argv)
    if args.fleet:
        path = merge_fleet(args.capture_dir, args.out)
    else:
        path = merge_capture(args.capture_dir, args.out)
    print(path)  # tpulint: disable=print — the CLI's one output line
    if args.validate:
        with open(path) as f:
            problems = validate_merged_trace(
                json.load(f),
                require_replicas=2 if args.fleet else 0)
        if problems:
            print("\n".join(problems))  # tpulint: disable=print — CLI output
            return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
