#!/bin/sh
# tpulint CI gate — the ONE entry point CI calls.
#
# Runs all four analyzer passes (per-file rules, whole-program
# dataflow, concurrency, contracts) over the library, the tests and
# the tools themselves, emitting SARIF for CI annotators.  When a
# baseline snapshot exists (tools/tpulint_baseline.json, written with
# --write-baseline) it is subtracted so only NEW findings fail the
# gate.  Extra flags pass through: e.g.  tools/lint_gate.sh --changed
#
# Exit code: 0 clean (or fully baselined), 1 on new findings —
# documented in docs/TPULINT.md.
set -eu
cd "$(dirname "$0")/.."

BASELINE="tools/tpulint_baseline.json"
if [ -f "$BASELINE" ]; then
    exec python -m tools.tpulint deepspeed_tpu tests tools \
        --format sarif --baseline "$BASELINE" "$@"
fi
exec python -m tools.tpulint deepspeed_tpu tests tools \
    --format sarif "$@"
