"""Per-fusion profile of the llama3-8b int8 DECODE burst (VERDICT r3
item 2: decode got a 'weight-traffic-bound' claim with no committed
profile; training got an hlo_stats budget in round 3 — this does the
same for decode).

Builds the exact bench engine (bench.py llama8b_serving_bench shapes)
WITH device telemetry on, runs warm decode bursts under the jax
profiler, and prints the top fusions by self-time with their
Compute/HBM bound_by attribution, plus the step-level accounting
(ms/burst, ms/token/seq) against the weight-read floor — the floor now
COMPUTED from the burst program's own ``cost_analysis`` bytes via the
engine's device telemetry (telemetry/device.py), not hand-written
constants.

Run on the real chip:  python tools/profile_decode8b.py
Artifacts: /tmp/decode8b_trace (xplane), /tmp/decode8b_hlo_stats.tsv
"""
# tpulint: disable-file=print — profiling CLI: the fusion table and
# step accounting ARE the tool's stdout deliverable

import glob
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np


def main():
    import jax

    from bench import _synthetic_int8_llama
    from deepspeed_tpu.inference import (InferenceConfig, InferenceEngine,
                                         SamplingParams)
    from deepspeed_tpu.models.presets import PRESETS
    from deepspeed_tpu.models.transformer import Model, TransformerConfig

    on_tpu = jax.devices()[0].platform == "tpu"
    n_seqs, prompt_len = (8, 512) if on_tpu else (2, 8)
    preset = dict(PRESETS["llama3-8b" if on_tpu else "llama-tiny"])
    preset["max_seq_len"] = 2048
    if not on_tpu:
        preset.update(vocab_size=512, num_layers=2, d_model=128,
                      num_heads=4, num_kv_heads=2, d_ff=352)
    cfg = TransformerConfig(**preset)
    dense, quant = _synthetic_int8_llama(cfg)
    model = Model.from_params(cfg, dense)
    eng = InferenceEngine(model, InferenceConfig(
        token_budget=1024 if on_tpu else 16, max_seqs=n_seqs,
        kv_block_size=64 if on_tpu else 16,
        num_kv_blocks=128 if on_tpu else 32,
        decode_burst=8 if on_tpu else 2,
        device_telemetry="on"), quant_tree=quant)

    r = np.random.RandomState(0)
    vocab = cfg.vocab_size
    sp = SamplingParams(temperature=0.0, max_new_tokens=1 << 30)

    # prompts in, prefill to steady decode state
    for uid in range(n_seqs):
        eng.put(uid, list(r.randint(0, vocab, prompt_len)))
    done = set()
    while len(done) < n_seqs:
        done.update(eng.step(sampling=sp).keys())

    for uid in range(n_seqs):
        eng.put(uid, [1])
    out = eng.decode_burst(sampling=sp)      # compile + settle
    for uid in out:
        eng.put(uid, [out[uid][-1]])
    out = eng.decode_burst(sampling=sp)      # warm

    # ---- timed + traced bursts -----------------------------------------
    # ONE profiler entry point (telemetry/profiler.py): the capture
    # window owns the jax.profiler session, the clock anchor, and the
    # loud absent-profiler degradation; each burst counts as one window
    # step, so `rounds` bursts complete it.  The same seam serves the
    # serving loop's anomaly-armed captures and bench --profile.
    trace_dir = "/tmp/decode8b_trace"
    eng.capture(steps=3, reason="decode8b", out_dir=trace_dir)
    t0 = time.perf_counter()
    rounds = 3
    toks = 0
    for _ in range(rounds):
        for uid in out:
            eng.put(uid, [out[uid][-1]])
        out = eng.decode_burst(sampling=sp)
        toks += sum(len(v) for v in out.values())
    dt = time.perf_counter() - t0
    capture_dir = eng.capture_dirs[-1] if eng.capture_dirs else None
    merged = None
    if capture_dir:
        from tools.tracemerge import merge_capture
        merged = merge_capture(capture_dir)

    burst = eng.icfg.decode_burst
    per_tok_ms = dt / rounds / burst * 1e3
    # the floor, measured instead of asserted: the burst program's own
    # cost_analysis bytes over the chip's published HBM bandwidth
    # (device telemetry probed it at the burst's compile; the same
    # numbers land in the BENCH JSON's llama8b device_metrics)
    ds = eng.device_snapshot()
    burst_cost = next((c for k, c in ds["programs"].items()
                       if k.startswith("('b'")), {})
    bw = ds["peak_hbm_bw"] or 0.7e12      # fallback: measured ~700GB/s
    floor_ms = burst_cost.get("bytes_accessed", 0) / bw * 1e3
    print(json.dumps({
        "ms_per_burst": round(dt / rounds * 1e3, 1),
        "tokens_per_burst": toks // rounds,
        "ms_per_token_per_seq": round(per_tok_ms, 1),
        "decode_tok_s_aggregate": round(toks / dt, 1),
        "burst_flops": burst_cost.get("flops"),
        "burst_bytes_accessed": burst_cost.get("bytes_accessed"),
        "hbm_floor_ms_per_burst": round(floor_ms, 1) if floor_ms
        else None,
        "floor_ratio": round(dt / rounds * 1e3 / floor_ms, 2)
        if floor_ms else None,
        "mfu": ds["mfu"],
        "hbm_bw_util": ds["hbm_bw_util"],
        "memory": ds["memory"],
        "capture_dir": capture_dir,
        "merged_timeline": merged,
    }))

    # ---- hlo_stats dump -------------------------------------------------
    paths = sorted(glob.glob((capture_dir or trace_dir)
                             + "/**/*.xplane.pb", recursive=True))
    if not paths:
        print("no xplane captured (profiler absent on this "
              "backend/build, or CPU-only jaxlib) — the merged "
              "host-side timeline above is still written")
        return
    try:
        from xprof.convert import raw_to_tool_data as rtd
    except ImportError as e:
        print(f"xprof unavailable ({e}); xplane kept at {paths[-1]} — "
              "run the hlo_stats conversion on the rig")
        return
    data, _ = rtd.xspace_to_tool_data([paths[-1]], "hlo_stats", {})
    if isinstance(data, bytes):
        data = data.decode()
    with open("/tmp/decode8b_hlo_stats.tsv", "w") as out:
        out.write(data)
    # the tool emits json-ish rows; print the top self-time entries
    import csv
    import io
    rows = list(csv.reader(io.StringIO(data)))
    if not rows:
        print("empty hlo_stats")
        return
    head = rows[0]
    try:
        i_self = head.index("Total self time (us)")
    except ValueError:
        i_self = None
    print("\n=== top fusions by self time ===")
    if i_self is not None:
        body = sorted(rows[1:],
                      key=lambda r2: -float(r2[i_self] or 0))[:25]
        i_cat = head.index("HLO category") if "HLO category" in head else 0
        i_bb = (head.index("Bound by") if "Bound by" in head else None)
        i_name = (head.index("HLO name") if "HLO name" in head else 1)
        for r2 in body:
            bb = r2[i_bb] if i_bb is not None else "?"
            print(f"{float(r2[i_self]):>12.0f} us  {bb:>8}  "
                  f"{r2[i_cat][:20]:>20}  {r2[i_name][:80]}")
    else:
        print(data[:4000])


if __name__ == "__main__":
    main()
